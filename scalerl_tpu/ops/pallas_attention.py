"""Pallas TPU flash attention (forward + flash-style backward).

The hot op of the long-context path (``models/transformer.py`` /
``parallel/sequence.py``).  No counterpart exists in the reference — it has
no attention at all (SURVEY.md §5) — this kernel is part of the TPU build's
beyond-parity long-context stack: blockwise online-softmax attention that
never materializes the ``[T, T]`` score matrix.

Tiling: the kv dimension lives in the *grid* (innermost, sequential on
TPU), with the online-softmax accumulators in VMEM scratch that persists
across kv steps — so VMEM holds one ``[block_q, D]`` query tile, one
``[block_k, D]`` kv tile, and one ``[block_q, block_k]`` score tile at a
time, and HBM traffic stays O(T·D) per (batch, head).  Long contexts never
pull a full ``[T, D]`` K or V into VMEM.

Layout matches :func:`scalerl_tpu.ops.ring_attention.full_attention`:
``q/k/v`` are ``[B, T, H, D]`` and the result is ``[B, Tq, H, D]``, so the
kernel drops into ``TransformerPolicy``'s pluggable ``attn_fn`` seam — and
composes with ring attention's device-level sequence sharding.

Differentiable: a ``jax.custom_vjp`` implements the flash backward — the
probability tiles are recomputed from the saved log-sum-exp rather than
stored, one kernel gridded over q blocks for ``dq`` and one gridded over
k blocks for ``dk``/``dv`` (the FlashAttention-2 split, so neither kernel
needs cross-grid accumulation).

On CPU hosts (tests, this image) the kernels run in Pallas interpret mode;
on TPU they compile to Mosaic.  Scores/accumulators are float32 regardless
of input dtype (bf16 inputs feed the MXU directly).
"""

from __future__ import annotations

import functools
import os
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_SEG_BIG = 2**30  # sentinel above any real segment id (pad id is 0)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _mask_block(
    i, j, q_len: int, k_len: int, block_q: int, block_k: int, causal: bool
):
    """Validity mask for score tile (q block ``i``, k block ``j``)."""
    q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    mask = (k_pos < k_len) & (q_pos < q_len)
    if causal:
        mask = mask & (k_pos <= q_pos)
    return mask


def _causal_live(i, j, block_q: int, block_k: int):
    """Whether kv tile ``j`` intersects the causal triangle of q tile ``i``."""
    return j * block_k <= i * block_q + block_q - 1


# ----------------------------------------------------------------------
# forward: grid (B, H, nq, nk) — kv innermost, accumulators in scratch
# ----------------------------------------------------------------------
def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc,
    *, scale, causal, q_len, k_len, block_q, block_k, nk,
):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    live = _causal_live(i, j, block_q, block_k) if causal else (j >= 0)

    @pl.when(live)
    def _attend():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale  # [bq, D]
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)  # [bk, D]
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        mask = _mask_block(i, j, q_len, k_len, block_q, block_k, causal)
        s = jnp.where(mask, s, _NEG_INF)
        m = m_sc[:]
        l = l_sc[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), _NEG_INF, m) - safe_m)
        l_sc[:] = l * corr + p.sum(axis=-1, keepdims=True)
        m_sc[:] = m_new
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finish():
        l = l_sc[:]
        m = m_sc[:]
        o_ref[0, :, 0, :] = (acc_sc[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse = jnp.where(
            l[:, 0] > 0.0, m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-30)), _NEG_INF
        )
        lse_ref[0, 0, :] = lse


def _pad_t(x: jnp.ndarray, t_pad: int) -> jnp.ndarray:
    T = x.shape[1]
    if T == t_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, t_pad - T), (0, 0), (0, 0)))


def _blocks(Tq: int, Tk: int, block_q: int, block_k: int):
    bq = min(block_q, _round_up(Tq, 8))
    bk = min(block_k, _round_up(Tk, 8))
    Tq_p, Tk_p = _round_up(Tq, bq), _round_up(Tk, bk)
    return bq, bk, Tq_p, Tk_p


def _fwd(
    q, k, v, causal, scale, block_q, block_k, interpret
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq, bk, Tq_p, Tk_p = _blocks(Tq, Tk, block_q, block_k)
    nq, nk = Tq_p // bq, Tk_p // bk
    qp, kp, vp = _pad_t(q, Tq_p), _pad_t(k, Tk_p), _pad_t(v, Tk_p)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, q_len=Tq, k_len=Tk,
        block_q=bq, block_k=bk, nk=nk,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, i, j: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, i, j: (b, j, h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, h, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Tq_p, H, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return o[:, :Tq], lse


# ----------------------------------------------------------------------
# backward (FlashAttention-2 split: dq over q blocks, dk/dv over k blocks)
# ----------------------------------------------------------------------
def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc,
    *, scale, causal, q_len, k_len, block_q, block_k, nk,
):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    live = _causal_live(i, j, block_q, block_k) if causal else (j >= 0)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale
        do = do_ref[0, :, 0, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]
        safe_lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        mask = _mask_block(i, j, q_len, k_len, block_q, block_k, causal)
        p = jnp.where(mask, jnp.exp(s - safe_lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0, :, 0, :] = (dq_sc[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_sc, dv_sc,
    *, scale, causal, q_len, k_len, block_q, block_k, nq,
):
    j = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    live = _causal_live(i, j, block_q, block_k) if causal else (i >= 0)

    @pl.when(live)
    def _accumulate():
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)  # [bk, D]
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale  # [bq, D]
        do = do_ref[0, :, 0, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]
        safe_lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        mask = _mask_block(i, j, q_len, k_len, block_q, block_k, causal)
        p = jnp.where(mask, jnp.exp(s - safe_lse), 0.0)  # [bq, bk]
        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        # q was pre-scaled, so ds@q carries one factor of `scale` already —
        # the remaining factor belongs to dq only
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0, :, 0, :] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, :, 0, :] = dv_sc[:].astype(dv_ref.dtype)


def _bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    q, k, v, o, lse = residuals
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq, bk, Tq_p, Tk_p = _blocks(Tq, Tk, block_q, block_k)
    nq, nk = Tq_p // bq, Tk_p // bk
    qp, kp, vp = _pad_t(q, Tq_p), _pad_t(k, Tk_p), _pad_t(v, Tk_p)
    dop, op = _pad_t(g, Tq_p), _pad_t(o, Tq_p)
    lse_p = jnp.pad(lse, ((0, 0), (0, 0), (0, Tq_p - Tq)))
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian correction term
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", dop.astype(jnp.float32), op.astype(jnp.float32)
    )

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, q_len=Tq, k_len=Tk,
        block_q=bq, block_k=bk, nk=nk,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, i, j: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, i, j: (b, j, h, 0)),
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, h, i)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, h, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Tq_p, H, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, q_len=Tq, k_len=Tk,
        block_q=bq, block_k=bk, nq=nq,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, j, i: (b, i, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j, i: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j, i: (b, j, h, 0)),
            pl.BlockSpec((1, bq, 1, D), lambda b, h, j, i: (b, i, h, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, h, j, i: (b, h, i)),
            pl.BlockSpec((1, 1, bq), lambda b, h, j, i: (b, h, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j, i: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j, i: (b, j, h, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Tk_p, H, D), k.dtype),
            jax.ShapeDtypeStruct((B, Tk_p, H, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta)
    return dq[:, :Tq], dk[:, :Tk], dv[:, :Tk]


# ----------------------------------------------------------------------
# public op
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Blockwise exact attention; same contract as ``full_attention``.

    ``q/k/v``: ``[B, T, H, D]`` (Tq may differ from Tk).  ``interpret=None``
    auto-selects Pallas interpret mode off-TPU.
    """
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    o, lse = _fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    if scale is None:
        scale = 1.0 / (residuals[0].shape[-1] ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    return _bwd(causal, scale, block_q, block_k, interpret, residuals, g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ======================================================================
# segment-packed flash attention (the pad-free packed-learner kernel)
#
# Self-attention over rows that PACK several independent sequences (the
# ``genrl/rollout.py`` bin-packer's layout): ``segment_ids [B, T]`` give
# every token its sequence id within the row (0 = pad), and a token
# attends only causally WITHIN its own segment.  The kernel is the
# training-grade twin of :func:`flash_attention` — same tiling, same
# online-softmax accumulators, same FlashAttention-2 backward split —
# plus segment-id block masking: each (q block, k block) grid step first
# reduces the two id vectors to their live ranges (segments are
# contiguous and ascending inside a row, pad is a zero tail, so the
# nonzero ids in any block form one integer interval) and SKIPS the
# matmuls entirely when the intervals cannot intersect — cross-segment
# and pad-only blocks cost two [block] reductions, never a [bq, bk]
# score tile.  That block skip is where the packed learner's FLOPs go
# from O(rows * T^2) to O(sum of per-segment len^2).
# ======================================================================


def _seg_ranges(seg_vec):
    """(min nonzero id, max id) of one block's id vector (pad = 0)."""
    hi = jnp.max(seg_vec)
    lo = jnp.min(jnp.where(seg_vec > 0, seg_vec, jnp.int32(_SEG_BIG)))
    return lo, hi


def _seg_block_live(i, j, q_seg, k_seg, block_q: int, block_k: int):
    """Whether any (q, k) pair in tile (i, j) shares a live segment."""
    q_lo, q_hi = _seg_ranges(q_seg)
    k_lo, k_hi = _seg_ranges(k_seg)
    return (
        _causal_live(i, j, block_q, block_k)
        & (q_hi > 0)
        & (k_hi > 0)
        & (q_lo <= k_hi)
        & (k_lo <= q_hi)
    )


def _seg_mask_block(
    i, j, q_seg, k_seg, q_len: int, block_q: int, block_k: int
):
    """[bq, bk] validity: in-bounds, causal, same nonzero segment."""
    mask = _mask_block(i, j, q_len, q_len, block_q, block_k, causal=True)
    return mask & (q_seg[:, None] == k_seg[None, :]) & (q_seg[:, None] > 0)


def _seg_fwd_kernel(
    q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref,
    acc_sc, m_sc, l_sc,
    *, scale, q_len, block_q, block_k, nk,
):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    q_seg = qseg_ref[0, :]
    k_seg = kseg_ref[0, :]
    live = _seg_block_live(i, j, q_seg, k_seg, block_q, block_k)

    @pl.when(live)
    def _attend():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        mask = _seg_mask_block(i, j, q_seg, k_seg, q_len, block_q, block_k)
        s = jnp.where(mask, s, _NEG_INF)
        m = m_sc[:]
        l = l_sc[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), _NEG_INF, m) - safe_m)
        l_sc[:] = l * corr + p.sum(axis=-1, keepdims=True)
        m_sc[:] = m_new
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nk - 1)
    def _finish():
        l = l_sc[:]
        m = m_sc[:]
        # fully-masked rows (pad queries) emit exact zeros, matching the
        # reference — their outputs are unused but must stay finite
        o_ref[0, :, 0, :] = (
            acc_sc[:] / jnp.maximum(l, 1e-30)
        ).astype(o_ref.dtype)
        lse = jnp.where(
            l[:, 0] > 0.0,
            m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-30)),
            _NEG_INF,
        )
        lse_ref[0, 0, :] = lse


def _pad_seg(seg: jnp.ndarray, t_pad: int) -> jnp.ndarray:
    T = seg.shape[1]
    if T == t_pad:
        return seg
    # pad tail rides segment id 0 -> masked everywhere by construction
    return jnp.pad(seg, ((0, 0), (0, t_pad - T)))


def _seg_fwd(q, k, v, seg, scale, block_q, block_k, interpret):
    B, T, H, D = q.shape
    bq, bk, T_p, _ = _blocks(T, T, block_q, block_k)
    nq, nk = T_p // bq, T_p // bk
    qp, kp, vp = _pad_t(q, T_p), _pad_t(k, T_p), _pad_t(v, T_p)
    segp = _pad_seg(seg.astype(jnp.int32), T_p)

    kernel = functools.partial(
        _seg_fwd_kernel, scale=scale, q_len=T,
        block_q=bq, block_k=bk, nk=nk,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, i, j: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, i, j: (b, j, h, 0)),
            pl.BlockSpec((1, bq), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, bk), lambda b, h, i, j: (b, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, h, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T_p, H, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, segp, segp)
    return o[:, :T], lse


def _seg_bwd_dq_kernel(
    q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_sc,
    *, scale, q_len, block_q, block_k, nk,
):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    q_seg = qseg_ref[0, :]
    k_seg = kseg_ref[0, :]
    live = _seg_block_live(i, j, q_seg, k_seg, block_q, block_k)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale
        do = do_ref[0, :, 0, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]
        safe_lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        mask = _seg_mask_block(i, j, q_seg, k_seg, q_len, block_q, block_k)
        p = jnp.where(mask, jnp.exp(s - safe_lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0, :, 0, :] = (dq_sc[:] * scale).astype(dq_ref.dtype)


def _seg_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_sc, dv_sc,
    *, scale, q_len, block_q, block_k, nq,
):
    j = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q_seg = qseg_ref[0, :]
    k_seg = kseg_ref[0, :]
    live = _seg_block_live(i, j, q_seg, k_seg, block_q, block_k)

    @pl.when(live)
    def _accumulate():
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        q = q_ref[0, :, 0, :].astype(jnp.float32) * scale
        do = do_ref[0, :, 0, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]
        safe_lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        mask = _seg_mask_block(i, j, q_seg, k_seg, q_len, block_q, block_k)
        p = jnp.where(mask, jnp.exp(s - safe_lse), 0.0)
        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        # q carries one factor of `scale` already (same split as the
        # causal kernel): the remaining factor belongs to dq only
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0, :, 0, :] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, :, 0, :] = dv_sc[:].astype(dv_ref.dtype)


def _seg_bwd(scale, block_q, block_k, interpret, residuals, g):
    q, k, v, seg, o, lse = residuals
    B, T, H, D = q.shape
    bq, bk, T_p, _ = _blocks(T, T, block_q, block_k)
    nq, nk = T_p // bq, T_p // bk
    qp, kp, vp = _pad_t(q, T_p), _pad_t(k, T_p), _pad_t(v, T_p)
    segp = _pad_seg(seg.astype(jnp.int32), T_p)
    dop, op = _pad_t(g, T_p), _pad_t(o, T_p)
    lse_p = jnp.pad(lse, ((0, 0), (0, 0), (0, T_p - T)))
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", dop.astype(jnp.float32), op.astype(jnp.float32)
    )

    dq_kernel = functools.partial(
        _seg_bwd_dq_kernel, scale=scale, q_len=T,
        block_q=bq, block_k=bk, nk=nk,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, i, j: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, i, j: (b, j, h, 0)),
            pl.BlockSpec((1, bq), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, bk), lambda b, h, i, j: (b, j)),
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, h, i)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, h, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, bq, 1, D), lambda b, h, i, j: (b, i, h, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, T_p, H, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, segp, segp, dop, lse_p, delta)

    dkv_kernel = functools.partial(
        _seg_bwd_dkv_kernel, scale=scale, q_len=T,
        block_q=bq, block_k=bk, nq=nq,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, j, i: (b, i, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j, i: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j, i: (b, j, h, 0)),
            pl.BlockSpec((1, bq), lambda b, h, j, i: (b, i)),
            pl.BlockSpec((1, bk), lambda b, h, j, i: (b, j)),
            pl.BlockSpec((1, bq, 1, D), lambda b, h, j, i: (b, i, h, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, h, j, i: (b, h, i)),
            pl.BlockSpec((1, 1, bq), lambda b, h, j, i: (b, h, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j, i: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j, i: (b, j, h, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, T_p, H, D), k.dtype),
            jax.ShapeDtypeStruct((B, T_p, H, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, segp, segp, dop, lse_p, delta)
    return dq[:, :T], dk[:, :T], dv[:, :T]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def segment_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Segment-packed causal self-attention, forward AND backward.

    ``q/k/v``: ``[B, T, H, D]`` with T shared (self-attention over packed
    rows).  ``segment_ids``: ``[B, T]`` int32, contiguous ascending ids
    starting at 1 with a zero pad tail (the ``genrl/rollout.py`` packer's
    contract).  Token ``i`` attends to ``j <= i`` iff
    ``segment_ids[i] == segment_ids[j] != 0``.  Fully-masked rows (pad
    queries) emit exact zeros.  ``interpret=None`` auto-selects Pallas
    interpret mode off-TPU.
    """
    out, _ = _segment_flash_fwd(
        q, k, v, segment_ids, scale, block_q, block_k, interpret
    )
    return out


def _segment_flash_fwd(q, k, v, seg, scale, block_q, block_k, interpret):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    o, lse = _seg_fwd(q, k, v, seg, scale, block_q, block_k, interpret)
    return o, (q, k, v, seg, o, lse)


def _segment_flash_bwd(scale, block_q, block_k, interpret, residuals, g):
    if scale is None:
        scale = 1.0 / (residuals[0].shape[-1] ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    dq, dk, dv = _seg_bwd(scale, block_q, block_k, interpret, residuals, g)
    # int segment ids are non-differentiable: their cotangent is float0
    dseg = np.zeros(residuals[3].shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dseg


segment_flash_attention.defvjp(_segment_flash_fwd, _segment_flash_bwd)


def segment_attention_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Dense XLA oracle for :func:`segment_flash_attention` — values AND
    gradients, including the exact-zero output at fully-masked (pad)
    rows.  Materializes the ``[T, T]`` scores: the parity reference and
    the off-TPU fallback shape, never the TPU hot path."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    seg = segment_ids.astype(jnp.int32)
    T = q.shape[1]
    causal = jnp.arange(T)[None, :, None] >= jnp.arange(T)[None, None, :]
    mask = (
        causal
        & (seg[:, :, None] == seg[:, None, :])
        & (seg[:, :, None] > 0)
    )  # [B, T, T]
    scores = (
        jnp.einsum(
            "bthd,bshd->bhts",
            q.astype(jnp.float32),
            k.astype(jnp.float32),
        )
        * scale
    )
    scores = jnp.where(mask[:, None, :, :], scores, jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1)
    # zero (not uniform) on fully-masked rows, matching the kernel
    probs = jnp.where(
        jnp.any(mask, axis=-1)[:, None, :, None], probs, 0.0
    )
    out = jnp.einsum("bhts,bshd->bthd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def resolve_segment_attn(impl: str = "auto") -> str:
    """``pallas`` on TPU, ``xla`` elsewhere; ``SCALERL_SEGMENT_ATTN``
    overrides what ``auto`` resolves to (the ``SCALERL_PAGED_ATTN`` /
    ``SCALERL_ITER_MODE`` escape-hatch pattern)."""
    impls = ("pallas", "xla")
    if impl == "auto":
        impl = os.environ.get("SCALERL_SEGMENT_ATTN", "") or (
            "pallas" if jax.default_backend() == "tpu" else "xla"
        )
    if impl not in impls:
        raise ValueError(
            f"segment attention impl must be auto | pallas | xla, got "
            f"{impl!r}"
        )
    return impl


def make_segment_attn_fn(impl: str = "auto") -> Optional[Callable]:
    """The ``TransformerPolicy.segment_attn_fn`` seam: resolve once,
    close over the choice.  Returns ``None`` for ``xla`` — the model then
    builds the dense packed mask and rides its existing
    ``_masked_attention`` path, which XLA fuses better than an
    interpret-mode kernel off-TPU."""
    if resolve_segment_attn(impl) == "pallas":
        return segment_flash_attention
    return None
