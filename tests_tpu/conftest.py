"""TPU-gated test suite: runs ONLY against a live TPU backend.

Deliberately separate from ``tests/`` (whose conftest force-pins the CPU
backend): everything here exists to exercise *compiled* TPU execution —
Pallas kernel tiling/VMEM legality, bf16 numerics on the MXU — which
interpret mode on CPU cannot validate (``ops/pallas_attention.py:27``).

Invoke explicitly when the tunnel is up:

    python -m pytest tests_tpu -q

Every test is marked ``tpu`` and the whole session skips unless
``jax.default_backend() == "tpu"`` — a CPU-only host skips cleanly rather
than failing.  NOTE: merely importing jax here touches the backend; under
a wedged axon tunnel that can hang, so run this suite with an external
timeout when probing.
"""

import pytest


def pytest_collection_modifyitems(config, items):
    for item in items:
        item.add_marker(pytest.mark.tpu)


def pytest_sessionstart(session):
    import jax

    backend = jax.default_backend()
    if backend != "tpu":
        session.config._scalerl_skip_all = f"backend is {backend!r}, not tpu"
        return
    # persistent compilation cache: this suite compiles the same programs
    # every tunnel contact, and round 5 saw a contact window shorter than
    # one suite run — warm-cache reruns must not re-pay the compiles
    from scalerl_tpu.utils.platform import setup_platform

    setup_platform("auto")


@pytest.fixture(autouse=True)
def _require_tpu(request):
    reason = getattr(request.config, "_scalerl_skip_all", None)
    if reason:
        pytest.skip(reason)
