"""First-class compiled-TPU validation of the Pallas kernels.

Until these run on a real chip, interpret-mode tests validate only
*semantics* — tiling and VMEM legality can still fail to compile
(VERDICT r2 weak #4).  Each test here forces ``interpret=False`` and
compares against the XLA reference implementation on-device.

Evidence protocol: when this file passes on a live tunnel, record the
run (date + device kind + pytest summary) in ``BENCH_TPU.md``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalerl_tpu.ops.pallas_attention import flash_attention
from scalerl_tpu.ops.pallas_per import (
    hierarchical_sample,
    pallas_sample,
    proportional_sample,
)
from scalerl_tpu.ops.ring_attention import full_attention


def _rand(key, *shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype=dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_compiled(causal):
    # TPU-legal tiles: block 128, head dim 128-lane friendly
    B, T, H, D = 2, 256, 4, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = _rand(k1, B, T, H, D), _rand(k2, B, T, H, D), _rand(k3, B, T, H, D)
    out = flash_attention(q, k, v, causal=causal, interpret=False)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_flash_forward_compiled_ragged_tail():
    # T not a block multiple: the padding/masking path must tile legally too
    B, T, H, D = 1, 200, 2, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = _rand(k1, B, T, H, D), _rand(k2, B, T, H, D), _rand(k3, B, T, H, D)
    out = flash_attention(q, k, v, causal=True, interpret=False)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_compiled(causal):
    B, T, H, D = 1, 256, 2, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = _rand(k1, B, T, H, D), _rand(k2, B, T, H, D), _rand(k3, B, T, H, D)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=False) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3, rtol=5e-3)


def test_flash_bfloat16_compiled():
    B, T, H, D = 2, 256, 2, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand(k1, B, T, H, D, dtype=jnp.bfloat16)
    k = _rand(k2, B, T, H, D, dtype=jnp.bfloat16)
    v = _rand(k3, B, T, H, D, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=False)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=5e-2, rtol=5e-2
    )


def test_pallas_per_sample_compiled():
    rng = np.random.default_rng(0)
    flat_p = jnp.asarray(rng.integers(1, 17, size=4096).astype(np.float32))
    total = float(jnp.sum(flat_p))
    u = rng.uniform(size=128)
    targets = jnp.asarray((np.arange(128) + u) / 128 * total, jnp.float32)
    compiled = pallas_sample(flat_p, targets, block_size=1024, interpret=False)
    ref = hierarchical_sample(flat_p, targets, block_size=1024)
    np.testing.assert_array_equal(np.asarray(compiled), np.asarray(ref))
    # and both agree with the O(n) cumsum reference
    ref2 = proportional_sample(flat_p, targets, method="cumsum")
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(ref2))
    # on this backend the default "auto" must route to the Pallas kernel
    # (VERDICT r4 #7: the flagship Ape-X/R2D2 paths use it the day
    # hardware answers), and produce the same sample
    from scalerl_tpu.ops.pallas_per import resolve_sample_method

    assert resolve_sample_method("auto") == "pallas"
    auto = proportional_sample(flat_p, targets)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))


def test_fused_loop_one_chunk_on_tpu():
    """The bench-shaped fused actor-learner program compiles and executes
    end to end on the chip (the headline path of ``bench.py``) — at a
    reduced batch so this stays a quick smoke, not a benchmark."""
    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

    args = ImpalaArguments(
        use_lstm=False, hidden_size=512, rollout_length=20, batch_size=64,
        max_timesteps=0, compute_dtype="bfloat16", logger_backend="none",
    )
    env = SyntheticPixelEnv()
    venv = JaxVecEnv(env, num_envs=64)
    agent = ImpalaAgent(args, obs_shape=env.observation_shape,
                        num_actions=env.num_actions)
    loop = DeviceActorLearnerLoop(
        model=agent.model, venv=venv, learn_fn=agent.make_learn_fn(),
        unroll_length=20, iters_per_call=2,
    )
    carry = loop.init_carry(jax.random.PRNGKey(0))
    state, carry, m = loop.train_chunk(agent.state, carry, jax.random.PRNGKey(1))
    assert np.isfinite(float(m["total_loss"]))


def test_breakout_fused_chunk_on_tpu():
    """The flagship Breakout game + fused IMPALA iteration compiles and
    executes on the chip (the wall-clock-to-score path of
    examples/curves/impala.py::impala_breakout)."""
    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs import JaxBreakout
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

    args = ImpalaArguments(
        use_lstm=False, hidden_size=256, rollout_length=20, batch_size=32,
        max_timesteps=0, logger_backend="none",
    )
    env = JaxBreakout()
    venv = JaxVecEnv(env, num_envs=32)
    agent = ImpalaAgent(args, obs_shape=env.observation_shape,
                        num_actions=env.num_actions)
    loop = DeviceActorLearnerLoop(
        model=agent.model, venv=venv, learn_fn=agent.make_learn_fn(),
        unroll_length=20, iters_per_call=2,
    )
    carry = loop.init_carry(jax.random.PRNGKey(0))
    state, carry, m = loop.train_chunk(agent.state, carry, jax.random.PRNGKey(1))
    assert np.isfinite(float(m["total_loss"]))


def test_device_r2d2_fused_iteration_on_tpu():
    """The fused R2D2 iteration (collect + sequence-replay insert +
    train_intensity learn steps + priority write-back as ONE program)
    compiles and executes on the chip."""
    from scalerl_tpu.agents.r2d2 import R2D2Agent
    from scalerl_tpu.config import R2D2Arguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.envs.jax_envs.recall import JaxRecall
    from scalerl_tpu.trainer.r2d2_device import DeviceR2D2Trainer

    args = R2D2Arguments(
        env_id="JaxRecall", rollout_length=8, burn_in=2, n_steps=1,
        batch_size=8, replay_capacity=64, warmup_sequences=8,
        use_lstm=True, hidden_size=64, logger_backend="none",
        logger_frequency=10**9, save_model=False,
    )
    env = JaxRecall(size=8, delay=2, num_cues=2)
    venv = JaxVecEnv(env, num_envs=8)
    agent = R2D2Agent(args, obs_shape=env.observation_shape, num_actions=2,
                      obs_dtype=jnp.uint8)
    trainer = DeviceR2D2Trainer(args, agent, venv, fused=True)
    result = trainer.train(total_frames=256)
    assert result["learn_steps"] > 0
    assert np.isfinite(result["total_loss"])
    trainer.close()


def test_sharded_replay_on_tpu_mesh():
    """Lane-sharded PER sampling under shard_map compiles on the TPU mesh
    (psum/pmax weight normalization + per-shard stratified draws).  On a
    single-chip tunnel this runs at dp=1 — one shard, but the lowering is
    the real composition the flagship paths use: the Pallas sample kernel
    (``auto`` resolves to it on TPU) inside shard_map with the size-1
    collectives, so hardware day can't be the first time it traces."""
    from scalerl_tpu.data.sharded_replay import ShardedPrioritizedReplay
    from scalerl_tpu.parallel import make_mesh

    n = jax.device_count()
    mesh = make_mesh(f"dp={n}")
    buf = ShardedPrioritizedReplay((8,), 16, mesh, num_envs=2 * n)
    rng = np.random.default_rng(0)
    for i in range(4):
        buf.add_with_priorities(
            {
                "obs": rng.normal(size=(2 * n, 8)).astype(np.float32),
                "next_obs": rng.normal(size=(2 * n, 8)).astype(np.float32),
                "action": rng.integers(0, 4, 2 * n).astype(np.int32),
                "reward": rng.normal(size=2 * n).astype(np.float32),
                "done": np.zeros(2 * n, bool),
            },
            rng.uniform(0.1, 2.0, 2 * n).astype(np.float32),
        )
    batch = buf.sample(2 * n, beta=0.4, key=jax.random.PRNGKey(0))
    assert np.isfinite(np.asarray(batch["weights"])).all()
    buf.update_priorities(batch["indices"], np.ones(2 * n, np.float32))


def test_transformer_flash_train_step_on_tpu():
    """One adam step through the Pallas flash-attention transformer on the
    chip — compiled blockwise attention in the BACKWARD pass too."""
    import optax

    from scalerl_tpu.models.transformer import TransformerPolicy

    model = TransformerPolicy(num_actions=4, d_model=128, num_heads=2,
                              num_layers=2, max_len=256, use_flash=True)
    obs = jax.random.normal(jax.random.PRNGKey(0), (4, 256, 16))
    params = model.init(jax.random.PRNGKey(1), obs)
    tx = optax.adam(1e-3)
    opt = tx.init(params)
    actions = jnp.zeros((4, 256), jnp.int32)

    @jax.jit
    def step(params, opt, obs):
        def loss_fn(p):
            out = model.apply(p, obs)
            logp = jax.nn.log_softmax(out.policy_logits)
            return -jnp.mean(jnp.take_along_axis(logp, actions[..., None], -1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt2 = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt2, loss

    params, opt, loss = step(params, opt, obs)
    assert np.isfinite(float(loss))


def test_vtrace_pallas_compiled():
    """Compiled (non-interpret) fused V-trace matches the scan reference
    on hardware — the Mosaic legality proof for ops/pallas_vtrace.py."""
    from scalerl_tpu.ops.pallas_vtrace import (
        vtrace_from_importance_weights_pallas,
    )
    from scalerl_tpu.ops.vtrace import vtrace_from_importance_weights

    rng = np.random.default_rng(7)
    T, B = 20, 128
    inp = dict(
        log_rhos=jnp.asarray(rng.normal(size=(T, B)) * 0.4, jnp.float32),
        discounts=jnp.asarray(0.99 * (rng.uniform(size=(T, B)) > 0.1), jnp.float32),
        rewards=jnp.asarray(rng.normal(size=(T, B)), jnp.float32),
        values=jnp.asarray(rng.normal(size=(T, B)), jnp.float32),
        bootstrap_value=jnp.asarray(rng.normal(size=(B,)), jnp.float32),
    )
    ref = vtrace_from_importance_weights(**inp)
    pal = jax.jit(
        lambda **kw: vtrace_from_importance_weights_pallas(**kw, interpret=False)
    )(**inp)
    np.testing.assert_allclose(
        np.asarray(ref.vs), np.asarray(pal.vs), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(ref.pg_advantages), np.asarray(pal.pg_advantages),
        atol=1e-5, rtol=1e-5,
    )


def test_per_update_blocks_compiled():
    """Compiled fused priority/sum-tree update matches the XLA reference,
    including a same-block revisit (the aliased-writeback hazard the
    idempotent per-block kernel design exists for)."""
    from scalerl_tpu.ops.pallas_per import update_priorities_blocks

    rng = np.random.default_rng(11)
    n, bs = 4096, 512
    flat = jnp.asarray(rng.uniform(0.1, 2.0, size=n), jnp.float32)
    sums = jnp.asarray(
        np.asarray(flat).reshape(-1, bs).sum(axis=1), jnp.float32
    )
    idx = jnp.asarray([10, 600, 700, 15, 4000], jnp.int32)  # block 0 twice
    newp = jnp.asarray([5.0, 4.0, 3.0, 2.0, 1.0], jnp.float32)
    ref_p, ref_s = update_priorities_blocks(
        flat, idx, newp, block_sums=sums, block_size=bs, method="xla"
    )
    pal_p, pal_s = update_priorities_blocks(
        flat, idx, newp, block_sums=sums, block_size=bs, method="pallas",
        interpret=False,
    )
    np.testing.assert_allclose(np.asarray(ref_p), np.asarray(pal_p), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref_s), np.asarray(pal_s), atol=1e-5)


def test_anakin_superchunk_one_dispatch_on_tpu():
    """run_anakin on hardware: N chunks of the 84x84 fused loop in one
    dispatch under the armed transfer guard."""
    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

    B, T = 64, 8
    args = ImpalaArguments(
        use_lstm=False, hidden_size=256, rollout_length=T, batch_size=B,
        max_timesteps=0, compute_dtype="bfloat16",
    )
    env = SyntheticPixelEnv()
    venv = JaxVecEnv(env, num_envs=B)
    agent = ImpalaAgent(
        args, obs_shape=env.observation_shape, num_actions=env.num_actions
    )
    loop = DeviceActorLearnerLoop(
        model=agent.model, venv=venv, learn_fn=agent.make_learn_fn(),
        unroll_length=T, iters_per_call=2,
    )
    key = jax.random.PRNGKey(0)
    carry = loop.init_carry(key)
    state, carry, metrics = loop.run_anakin(
        agent.state, carry, jax.random.PRNGKey(1), num_calls=3
    )
    # warm call runs under the armed guard
    state, carry, metrics = loop.run_anakin(
        state, carry, jax.random.PRNGKey(2), num_calls=3
    )
    assert metrics["chunks_done"] == 3.0
    assert np.isfinite(metrics["total_loss"])


def test_dp_mp_sharded_transformer_step_on_tpu():
    """The dp×mp sharded learner's pjit train step compiles and runs on
    the real chip topology: transformer policy with heads/mlp/vocab over
    the named ``mp`` axis, activations constrained batch-over-dp, state
    donated, bf16 params with fp32 optimizer state.  On a single-chip
    tunnel this runs at dp=1,mp=1 — the lowering (logical-rule
    NamedShardings + with_sharding_constraint + donation) is still the
    real program; with 2+ chips mp=2 exercises the collectives."""
    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.data.trajectory import Trajectory

    n = jax.device_count()
    mp = 2 if n % 2 == 0 and n >= 2 else 1
    spec = f"dp={n // mp},mp={mp}" if mp > 1 else f"dp={n}"
    T, B = 8, 4 * max(n // mp, 1)
    args = ImpalaArguments(
        policy_arch="transformer", d_model=128, n_heads=4, n_layers=2,
        bf16_params=True, rollout_length=T, batch_size=B, use_lstm=False,
        max_timesteps=0, num_actors=1, num_buffers=2,
    )
    agent = ImpalaAgent(
        args, obs_shape=(16,), num_actions=8, obs_dtype=jnp.float32
    )
    agent.enable_mesh(spec)
    if mp > 1:
        assert any(
            "mp" in [s for s in leaf.sharding.spec if s is not None]
            for leaf in jax.tree_util.tree_leaves(agent.state.params)
        )
    key = jax.random.PRNGKey(0)
    traj = Trajectory(
        obs=jax.random.normal(key, (T + 1, B, 16), jnp.float32),
        action=jax.random.randint(key, (T + 1, B), 0, 8, jnp.int32),
        reward=jax.random.normal(key, (T + 1, B), jnp.float32),
        done=jnp.zeros((T + 1, B), jnp.bool_),
        logits=jax.random.normal(key, (T + 1, B, 8), jnp.float32),
        core_state=(),
    )
    for _ in range(2):
        metrics = agent.learn(traj)
    assert np.isfinite(metrics["total_loss"])
    assert int(agent.state.step) == 2


def test_genrl_generation_round_on_tpu():
    """One KV-cached generation round compiled on the chip (ISSUE 10): the
    scan-fused decode loop at a TPU-shaped bucket pair, one dispatch + one
    batched read, and the decode logprobs must match the full masked
    forward recomputation on-device (the cache-vs-full parity proof under
    real tiling/bf16-free f32 attention)."""
    from scalerl_tpu.genrl.engine import GenerationConfig, GenerationEngine
    from scalerl_tpu.models.transformer import (
        TransformerPolicy,
        sequence_attention_mask,
        sequence_positions,
    )

    V, P, R, B = 256, 64, 64, 16
    model = TransformerPolicy(
        num_actions=V, vocab_size=V, d_model=128, num_heads=4,
        num_layers=2, max_len=P + R,
    )
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32))
    engine = GenerationEngine(
        model, params,
        GenerationConfig(vocab_size=V, max_prompt_len=P, max_new_tokens=R),
        iter_mode="scan",
    )
    rng = np.random.default_rng(0)
    prompts = rng.integers(2, V, size=(B, P)).astype(np.int32)
    lengths = rng.integers(P // 2, P + 1, size=B).astype(np.int32)
    result = engine.generate(prompts, lengths)
    result = engine.generate(prompts, lengths)  # warm round under the guard
    assert result.response_tokens.shape == (B, R)
    assert np.isfinite(result.behavior_logp).all()
    # on-device parity: recompute the sampling distribution from the full
    # masked forward over the packed sequences
    lens = jnp.asarray(result.prompt_len)
    S = P + R
    full = model.apply(
        params, jnp.asarray(result.sequences),
        positions=sequence_positions(lens, P, S),
        attn_mask=sequence_attention_mask(lens, P, S),
    )
    logp_all = jax.nn.log_softmax(full.policy_logits[:, P - 1:S - 1], -1)
    expect = np.take_along_axis(
        np.asarray(logp_all), result.response_tokens[..., None], axis=-1
    )[..., 0]
    np.testing.assert_allclose(result.behavior_logp, expect, atol=1e-3)


def test_paged_decode_attention_compiled():
    """The continuous-batching decode kernel (ISSUE 11) compiled on the
    chip: scalar-prefetch page-table indexing + online softmax at a
    TPU-legal head dim, pinned to the XLA gather reference on-device
    across a fragmented table with a partially-filled last page."""
    from scalerl_tpu.ops.pallas_paged_attention import (
        paged_attention_reference,
        paged_decode_attention,
    )

    B, H, D = 8, 4, 128
    N, ps, M = 33, 16, 4
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    q = _rand(k1, B, 1, H, D)
    k_pages = _rand(k2, N, ps, H, D)
    v_pages = _rand(k3, N, ps, H, D)
    rng = np.random.default_rng(7)
    # fragmented layout: every lane owns a random disjoint page set
    perm = rng.permutation(np.arange(1, N))[: B * M].reshape(B, M)
    table = jnp.asarray(perm, jnp.int32)
    lengths = jnp.asarray(rng.integers(1, M * ps + 1, size=B), jnp.int32)
    out = paged_decode_attention(
        q, k_pages, v_pages, table, lengths, interpret=False
    )
    ref = paged_attention_reference(q, k_pages, v_pages, table, lengths)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3
    )


def test_continuous_engine_macro_step_on_tpu():
    """One continuous-batching macro-step compiled on the chip: paged
    prefill into allocated pages, the fused multi-substep decode with the
    Pallas paged-attention kernel behind the attn seam, one batched read
    — and greedy parity against the fixed-cohort engine on-device."""
    from scalerl_tpu.genrl.continuous import (
        ContinuousConfig,
        ContinuousEngine,
    )
    from scalerl_tpu.genrl.engine import GenerationConfig, GenerationEngine
    from scalerl_tpu.models.transformer import TransformerPolicy

    V, P, R = 256, 64, 32
    model = TransformerPolicy(
        num_actions=V, vocab_size=V, d_model=128, num_heads=4,
        num_layers=2, max_len=2 * (P + R),
    )
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32))
    rng = np.random.default_rng(1)
    prompts = rng.integers(2, V, size=(4, P)).astype(np.int32)
    lengths = rng.integers(P // 2, P + 1, size=4).astype(np.int32)
    fixed = GenerationEngine(
        model, params,
        GenerationConfig(
            vocab_size=V, max_prompt_len=P, max_new_tokens=R,
            temperature=0.0,
        ),
        iter_mode="scan",
    )
    ref = fixed.generate(prompts, lengths)
    engine = ContinuousEngine(
        model, params,
        ContinuousConfig(
            vocab_size=V, max_prompt_len=P, max_new_tokens=R,
            temperature=0.0, lanes=8, page_size=16, steps_per_macro=8,
            paged_attn="pallas",
        ),
        iter_mode="scan",
    )
    for i in range(4):
        engine.submit(prompts[i], lengths[i])
    done = {
        tuple(c.prompt.tolist()): c
        for c in engine.run_until(4, max_macro_steps=30)
    }
    for i in range(4):
        c = done[tuple(prompts[i][: lengths[i]].tolist())]
        n = int(ref.response_len[i])
        np.testing.assert_array_equal(
            c.response_tokens, ref.response_tokens[i, :n]
        )
    assert engine._decode_traces == 1


def test_segment_flash_forward_backward_compiled():
    """ISSUE 15: the packed-learner segment flash kernel, fwd AND bwd,
    compiled on-chip — segment-blocked causal masking, skipped
    cross-segment/pad blocks, and the custom_vjp backward all tile
    legally at TPU-native blocks (128) and D=128."""
    from scalerl_tpu.ops.pallas_attention import (
        segment_attention_reference,
        segment_flash_attention,
    )

    B, T, H, D = 2, 384, 2, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = _rand(k1, B, T, H, D), _rand(k2, B, T, H, D), _rand(k3, B, T, H, D)
    # multi-segment rows with a pad tail: block-skip liveness exercises
    # cross-segment, pad-only, and boundary-straddling tiles
    seg = np.zeros((B, T), np.int32)
    seg[0, :100], seg[0, 100:260], seg[0, 260:330] = 1, 2, 3
    seg[1, :200] = 1
    seg = jnp.asarray(seg)
    out = segment_flash_attention(q, k, v, seg, None, 128, 128, False)
    ref = segment_attention_reference(q, k, v, seg)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3
    )

    def loss_kernel(q, k, v):
        o = segment_flash_attention(q, k, v, seg, None, 128, 128, False)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = segment_attention_reference(q, k, v, seg)
        return jnp.sum(o * o)

    gk = jax.jit(jax.grad(loss_kernel, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3, rtol=5e-3
        )
