"""First-class compiled-TPU validation of the Pallas kernels.

Until these run on a real chip, interpret-mode tests validate only
*semantics* — tiling and VMEM legality can still fail to compile
(VERDICT r2 weak #4).  Each test here forces ``interpret=False`` and
compares against the XLA reference implementation on-device.

Evidence protocol: when this file passes on a live tunnel, record the
run (date + device kind + pytest summary) in ``BENCH_TPU.md``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalerl_tpu.ops.pallas_attention import flash_attention
from scalerl_tpu.ops.pallas_per import (
    hierarchical_sample,
    pallas_sample,
    proportional_sample,
)
from scalerl_tpu.ops.ring_attention import full_attention


def _rand(key, *shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype=dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_compiled(causal):
    # TPU-legal tiles: block 128, head dim 128-lane friendly
    B, T, H, D = 2, 256, 4, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = _rand(k1, B, T, H, D), _rand(k2, B, T, H, D), _rand(k3, B, T, H, D)
    out = flash_attention(q, k, v, causal=causal, interpret=False)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


def test_flash_forward_compiled_ragged_tail():
    # T not a block multiple: the padding/masking path must tile legally too
    B, T, H, D = 1, 200, 2, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = _rand(k1, B, T, H, D), _rand(k2, B, T, H, D), _rand(k3, B, T, H, D)
    out = flash_attention(q, k, v, causal=True, interpret=False)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_compiled(causal):
    B, T, H, D = 1, 256, 2, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = _rand(k1, B, T, H, D), _rand(k2, B, T, H, D), _rand(k3, B, T, H, D)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=False) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3, rtol=5e-3)


def test_flash_bfloat16_compiled():
    B, T, H, D = 2, 256, 2, 128
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand(k1, B, T, H, D, dtype=jnp.bfloat16)
    k = _rand(k2, B, T, H, D, dtype=jnp.bfloat16)
    v = _rand(k3, B, T, H, D, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=False)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=5e-2, rtol=5e-2
    )


def test_pallas_per_sample_compiled():
    rng = np.random.default_rng(0)
    flat_p = jnp.asarray(rng.integers(1, 17, size=4096).astype(np.float32))
    total = float(jnp.sum(flat_p))
    u = rng.uniform(size=128)
    targets = jnp.asarray((np.arange(128) + u) / 128 * total, jnp.float32)
    compiled = pallas_sample(flat_p, targets, block_size=1024, interpret=False)
    ref = hierarchical_sample(flat_p, targets, block_size=1024)
    np.testing.assert_array_equal(np.asarray(compiled), np.asarray(ref))
    # and both agree with the O(n) cumsum reference
    ref2 = proportional_sample(flat_p, targets)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(ref2))


def test_fused_loop_one_chunk_on_tpu():
    """The bench-shaped fused actor-learner program compiles and executes
    end to end on the chip (the headline path of ``bench.py``) — at a
    reduced batch so this stays a quick smoke, not a benchmark."""
    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

    args = ImpalaArguments(
        use_lstm=False, hidden_size=512, rollout_length=20, batch_size=64,
        max_timesteps=0, compute_dtype="bfloat16", logger_backend="none",
    )
    env = SyntheticPixelEnv()
    venv = JaxVecEnv(env, num_envs=64)
    agent = ImpalaAgent(args, obs_shape=env.observation_shape,
                        num_actions=env.num_actions)
    loop = DeviceActorLearnerLoop(
        model=agent.model, venv=venv, learn_fn=agent.make_learn_fn(),
        unroll_length=20, iters_per_call=2,
    )
    carry = loop.init_carry(jax.random.PRNGKey(0))
    state, carry, m = loop.train_chunk(agent.state, carry, jax.random.PRNGKey(1))
    assert np.isfinite(float(m["total_loss"]))
