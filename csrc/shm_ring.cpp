// Lock-free shared-memory rollout ring for the actor->learner hot path.
//
// The runtime counterpart of the reference's free_queue/full_queue slot
// cycle (scalerl/impala/impala_atari.py:416-437), which paid a Python
// SimpleQueue + pickle round trip per slot handoff.  Here the two queues are
// Vyukov bounded MPMC rings of slot indices living in *caller-provided*
// shared memory (e.g. Python multiprocessing.shared_memory), so any number
// of actor processes and learner threads exchange trajectory slots with one
// atomic CAS each and zero serialization; slot payloads are written in
// place by numpy views over the same segment.
//
// Memory layout (64-byte aligned sections):
//   [RingHeader][free cells: num_slots_pow2][full cells: num_slots_pow2]
// Slot data lives wherever the caller wants (usually right after) — this
// module only manages indices.
//
// Build: g++ -O3 -shared -fPIC -o libsrl_ring.so shm_ring.cpp -lpthread

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

namespace {

constexpr uint32_t kMagic = 0x53524C52;  // "SRLR"

struct Cell {
  std::atomic<uint32_t> seq;
  uint32_t value;
};

struct Queue {
  alignas(64) std::atomic<uint32_t> head;  // enqueue ticket
  alignas(64) std::atomic<uint32_t> tail;  // dequeue ticket
};

struct RingHeader {
  uint32_t magic;
  uint32_t num_slots;
  uint32_t capacity;  // pow2 >= num_slots
  uint32_t mask;
  alignas(64) Queue free_q;
  alignas(64) Queue full_q;
  alignas(64) std::atomic<uint32_t> closed;
};

inline Cell* free_cells(RingHeader* h) {
  return reinterpret_cast<Cell*>(reinterpret_cast<char*>(h) + sizeof(RingHeader));
}

inline Cell* full_cells(RingHeader* h) {
  return free_cells(h) + h->capacity;
}

inline uint32_t pow2_at_least(uint32_t n) {
  uint32_t c = 1;
  while (c < n) c <<= 1;
  return c;
}

// Vyukov bounded MPMC enqueue; returns false when full.
bool q_push(Queue* q, Cell* cells, uint32_t mask, uint32_t value) {
  uint32_t pos = q->head.load(std::memory_order_relaxed);
  for (;;) {
    Cell* cell = &cells[pos & mask];
    uint32_t seq = cell->seq.load(std::memory_order_acquire);
    int32_t dif = static_cast<int32_t>(seq) - static_cast<int32_t>(pos);
    if (dif == 0) {
      if (q->head.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed)) {
        cell->value = value;
        cell->seq.store(pos + 1, std::memory_order_release);
        return true;
      }
    } else if (dif < 0) {
      return false;  // full
    } else {
      pos = q->head.load(std::memory_order_relaxed);
    }
  }
}

// Vyukov bounded MPMC dequeue; returns false when empty.
bool q_pop(Queue* q, Cell* cells, uint32_t mask, uint32_t* out) {
  uint32_t pos = q->tail.load(std::memory_order_relaxed);
  for (;;) {
    Cell* cell = &cells[pos & mask];
    uint32_t seq = cell->seq.load(std::memory_order_acquire);
    int32_t dif =
        static_cast<int32_t>(seq) - static_cast<int32_t>(pos + 1);
    if (dif == 0) {
      if (q->tail.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed)) {
        *out = cell->value;
        cell->seq.store(pos + mask + 1, std::memory_order_release);
        return true;
      }
    } else if (dif < 0) {
      return false;  // empty
    } else {
      pos = q->tail.load(std::memory_order_relaxed);
    }
  }
}

void sleep_us(long us) {
  timespec ts{0, us * 1000L};
  nanosleep(&ts, nullptr);
}

// Spin-then-sleep pop with deadline; timeout_us < 0 means block forever.
int timed_pop(RingHeader* h, Queue* q, Cell* cells, int64_t timeout_us,
              uint32_t* out) {
  int64_t waited = 0;
  int spins = 0;
  for (;;) {
    if (q_pop(q, cells, h->mask, out)) return 0;
    if (h->closed.load(std::memory_order_acquire)) return -2;
    if (timeout_us >= 0 && waited >= timeout_us) return -1;
    if (++spins < 64) continue;  // brief busy spin for low latency
    sleep_us(50);
    waited += 50;
  }
}

}  // namespace

extern "C" {

// Bytes needed for a ring managing num_slots indices.
uint64_t srl_ring_bytes(uint32_t num_slots) {
  uint32_t cap = pow2_at_least(num_slots);
  return sizeof(RingHeader) + 2ull * cap * sizeof(Cell);
}

// Initialize a ring in caller-provided zeroed memory; all slot indices
// start on the free queue.  Returns 0 on success.
int srl_ring_init(void* base, uint32_t num_slots) {
  auto* h = static_cast<RingHeader*>(base);
  h->num_slots = num_slots;
  h->capacity = pow2_at_least(num_slots);
  h->mask = h->capacity - 1;
  h->free_q.head.store(0);
  h->free_q.tail.store(0);
  h->full_q.head.store(0);
  h->full_q.tail.store(0);
  h->closed.store(0);
  Cell* fc = free_cells(h);
  Cell* uc = full_cells(h);
  for (uint32_t i = 0; i < h->capacity; ++i) {
    fc[i].seq.store(i, std::memory_order_relaxed);
    uc[i].seq.store(i, std::memory_order_relaxed);
  }
  for (uint32_t i = 0; i < num_slots; ++i) {
    q_push(&h->free_q, fc, h->mask, i);
  }
  h->magic = kMagic;
  std::atomic_thread_fence(std::memory_order_seq_cst);
  return 0;
}

int srl_ring_check(void* base) {
  return static_cast<RingHeader*>(base)->magic == kMagic ? 0 : -3;
}

// Actor: take a free slot index.  Returns slot index >= 0, -1 on timeout,
// -2 if the ring is closed.
int32_t srl_ring_acquire(void* base, int64_t timeout_us) {
  auto* h = static_cast<RingHeader*>(base);
  uint32_t idx;
  int rc = timed_pop(h, &h->free_q, free_cells(h), timeout_us, &idx);
  return rc == 0 ? static_cast<int32_t>(idx) : rc;
}

// Actor: publish a filled slot.
int srl_ring_commit(void* base, uint32_t idx) {
  auto* h = static_cast<RingHeader*>(base);
  return q_push(&h->full_q, full_cells(h), h->mask, idx) ? 0 : -4;
}

// Learner: take a filled slot index.
int32_t srl_ring_pop_full(void* base, int64_t timeout_us) {
  auto* h = static_cast<RingHeader*>(base);
  uint32_t idx;
  int rc = timed_pop(h, &h->full_q, full_cells(h), timeout_us, &idx);
  return rc == 0 ? static_cast<int32_t>(idx) : rc;
}

// Learner: recycle a consumed slot.
int srl_ring_release(void* base, uint32_t idx) {
  auto* h = static_cast<RingHeader*>(base);
  return q_push(&h->free_q, free_cells(h), h->mask, idx) ? 0 : -4;
}

void srl_ring_close(void* base) {
  static_cast<RingHeader*>(base)->closed.store(1, std::memory_order_release);
}

int srl_ring_closed(void* base) {
  return static_cast<RingHeader*>(base)->closed.load(std::memory_order_acquire);
}

// Parallel batch gather: copy n src pointers into one contiguous dst
// (the learner's stack-into-batch hot path).  Single-threaded memcpy is
// memory-bandwidth-bound already; this exists so the learner host can stack
// without the Python loop + np.concatenate temporaries.
void srl_gather_batch(char* dst, const char** srcs, uint32_t n,
                      uint64_t bytes_per_src) {
  for (uint32_t i = 0; i < n; ++i) {
    std::memcpy(dst + i * bytes_per_src, srcs[i], bytes_per_src);
  }
}

}  // extern "C"
